"""ActorModel semantics tests mirroring the reference's golden assertions
(ref: src/actor/model.rs:765-1600)."""

from stateright_tpu import Expectation, StateRecorder, PathRecorder
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    ActorModelState,
    Deliver,
    DropEnv,
    Envelope,
    Id,
    LossyNetwork,
    Network,
    model_timeout,
)
from stateright_tpu.actor.test_util import Ping, PingPongCfg, Pong


def test_visits_expected_states():
    # ref: src/actor/model.rs:774-892 — exact 14-state space of lossy
    # duplicating ping-pong with max_nat=1.
    def snap(states, envelopes, last_msg):
        return ActorModelState(
            actor_states=tuple(states),
            network=Network.new_unordered_duplicating_with_last_msg(
                envelopes, last_msg
            ),
            timers_set=(frozenset(), frozenset()),
            random_choices=({}, {}),
            crashed=(False, False),
            history=(0, 0),
        )

    e = lambda s, d, m: Envelope(Id(s), Id(d), m)  # noqa: E731

    recorder = StateRecorder()
    checker = (
        PingPongCfg(maintains_history=False, max_nat=1)
        .into_model()
        .with_lossy_network(LossyNetwork.YES)
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 14
    assert len(recorder.states) == 14

    expected = [
        # Lossless progressions.
        snap([0, 0], [e(0, 1, Ping(0))], None),
        snap([0, 1], [e(0, 1, Ping(0)), e(1, 0, Pong(0))], e(0, 1, Ping(0))),
        snap(
            [1, 1],
            [e(0, 1, Ping(0)), e(1, 0, Pong(0)), e(0, 1, Ping(1))],
            e(1, 0, Pong(0)),
        ),
        # Loss from state (0, 0).
        snap([0, 0], [], None),
        # Loss from state (0, 1).
        snap([0, 1], [e(1, 0, Pong(0))], e(0, 1, Ping(0))),
        snap([0, 1], [e(0, 1, Ping(0))], e(0, 1, Ping(0))),
        snap([0, 1], [], e(0, 1, Ping(0))),
        # Loss from state (1, 1).
        snap([1, 1], [e(1, 0, Pong(0)), e(0, 1, Ping(1))], e(1, 0, Pong(0))),
        snap([1, 1], [e(0, 1, Ping(0)), e(0, 1, Ping(1))], e(1, 0, Pong(0))),
        snap([1, 1], [e(0, 1, Ping(0)), e(1, 0, Pong(0))], e(1, 0, Pong(0))),
        snap([1, 1], [e(0, 1, Ping(1))], e(1, 0, Pong(0))),
        snap([1, 1], [e(1, 0, Pong(0))], e(1, 0, Pong(0))),
        snap([1, 1], [e(0, 1, Ping(0))], e(1, 0, Pong(0))),
        snap([1, 1], [], e(1, 0, Pong(0))),
    ]
    for exp in expected:
        assert exp in recorder.states, f"missing state {exp!r}"
    assert len(expected) == 14


def test_no_op_depends_on_network():
    # ref: src/actor/model.rs:894-967
    class Client(Actor):
        def __init__(self, server):
            self.server = server

        def on_start(self, id, out):
            out.send(self.server, "Ignored")
            out.send(self.server, "Interesting")
            return "Awaiting an interesting message."

        def on_msg(self, id, state, src, msg, out):
            if msg == "Interesting":
                return "Got an interesting message."
            return None

    class Server(Actor):
        def on_start(self, id, out):
            return "Awaiting an interesting message."

        def on_msg(self, id, state, src, msg, out):
            if msg == "Interesting":
                return "Got an interesting message."
            return None

    def build(network):
        return (
            ActorModel.new(None, None)
            .actor(Client(Id(1)))
            .actor(Server())
            .with_lossy_network(LossyNetwork.NO)
            .with_init_network(network)
            .property(Expectation.ALWAYS, "Check everything", lambda m, s: True)
        )

    assert (
        build(Network.new_unordered_duplicating()).checker().spawn_bfs().join()
        .unique_state_count()
        == 2
    )
    assert (
        build(Network.new_unordered_nonduplicating()).checker().spawn_bfs().join()
        .unique_state_count()
        == 2
    )
    # Ordered networks must pop the flow head even when delivery is a no-op.
    assert (
        build(Network.new_ordered()).checker().spawn_bfs().join()
        .unique_state_count()
        == 3
    )


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    # ref: src/actor/model.rs:969-982 — the 4,094-state golden.
    checker = (
        PingPongCfg(max_nat=5, maintains_history=False)
        .into_model()
        .with_lossy_network(LossyNetwork.YES)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    # ref: src/actor/model.rs:984-1006
    checker = (
        PingPongCfg(max_nat=5, maintains_history=False)
        .into_model()
        .with_lossy_network(LossyNetwork.YES)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4094
    checker.assert_discovery(
        "must reach max", [DropEnv(Envelope(Id(0), Id(1), Ping(0)))]
    )


def test_eventually_reaches_max_on_perfect_delivery_network():
    # ref: src/actor/model.rs:1008-1022 — the 11-state golden.
    checker = (
        PingPongCfg(max_nat=5, maintains_history=False)
        .into_model()
        .with_init_network(Network.new_unordered_nonduplicating())
        .with_lossy_network(LossyNetwork.NO)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    # ref: src/actor/model.rs:1024-1044
    checker = (
        PingPongCfg(max_nat=5, maintains_history=False)
        .into_model()
        .with_lossy_network(LossyNetwork.NO)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("can reach max").last_state().actor_states == (4, 5)


def test_might_never_reach_beyond_max():
    # ref: src/actor/model.rs:1046-1073 — falsifiable liveness via the boundary.
    checker = (
        PingPongCfg(max_nat=5, maintains_history=False)
        .into_model()
        .with_init_network(Network.new_unordered_nonduplicating())
        .with_lossy_network(LossyNetwork.NO)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("must exceed max").last_state().actor_states == (5, 5)


def test_handles_undeliverable_messages():
    # ref: src/actor/model.rs:1076-1092
    class Noop(Actor):
        def on_start(self, id, out):
            return ()

    checker = (
        ActorModel.new(None, None)
        .actor(Noop())
        .property(Expectation.ALWAYS, "unused", lambda m, s: True)
        .with_init_network(
            Network.new_unordered_duplicating([Envelope(Id(0), Id(99), ())])
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 1


def test_handles_ordered_network_flag():
    # ref: src/actor/model.rs:1094-1159
    class OrderedNetworkActor(Actor):
        def on_start(self, id, out):
            if id == 0:
                out.send(Id(1), 2)
                out.send(Id(1), 1)
            return ()

        def on_msg(self, id, state, src, msg, out):
            return state + (msg,)

    def build(network):
        return (
            ActorModel.new(None, None)
            .add_actors([OrderedNetworkActor(), OrderedNetworkActor()])
            .property(Expectation.ALWAYS, "any", lambda m, s: True)
            .with_init_network(network)
        )

    recorder = StateRecorder()
    build(Network.new_ordered()).checker().visitor(recorder).spawn_bfs().join()
    received = {s.actor_states[1] for s in recorder.states}
    assert received == {(), (2,), (2, 1)}

    recorder = StateRecorder()
    build(Network.new_unordered_nonduplicating()).checker().visitor(
        recorder
    ).spawn_bfs().join()
    received = {s.actor_states[1] for s in recorder.states}
    assert received == {(), (1,), (2,), (1, 2), (2, 1)}


def test_unordered_network_semantics():
    # ref: src/actor/model.rs:1161-1274 — the duplicating-network regression:
    # "drop" on a duplicating network means "never deliver again".
    class A(Actor):
        def on_start(self, id, out):
            if id == 0:
                out.send(Id(1), "m")
                out.send(Id(1), "m")
            return 0

        def on_msg(self, id, state, src, msg, out):
            return state + 1

    def action_sequences(lossy, network):
        recorder = PathRecorder()
        (
            ActorModel.new(None, None)
            .add_actors([A(), A()])
            .with_init_network(network)
            .with_lossy_network(lossy)
            .property(Expectation.ALWAYS, "force visiting all states", lambda m, s: True)
            .with_within_boundary(lambda cfg, s: s.actor_states[1] < 4)
            .checker()
            .visitor(recorder)
            .spawn_dfs()
            .join()
        )
        return {tuple(p.actions()) for p in recorder.paths}

    deliver = Deliver(Id(0), Id(1), "m")
    drop = DropEnv(Envelope(Id(0), Id(1), "m"))

    # Ordered: both messages deliverable/droppable, no third.
    ordered_lossless = action_sequences(LossyNetwork.NO, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossless
    assert (deliver, deliver, deliver) not in ordered_lossless
    ordered_lossy = action_sequences(LossyNetwork.YES, Network.new_ordered())
    assert (deliver, deliver) in ordered_lossy
    assert (deliver, drop) in ordered_lossy
    assert (drop, drop) in ordered_lossy

    # Unordered duplicating: unlimited redelivery; drop ends delivery.
    ud_lossless = action_sequences(
        LossyNetwork.NO, Network.new_unordered_duplicating()
    )
    assert (deliver, deliver, deliver) in ud_lossless
    ud_lossy = action_sequences(LossyNetwork.YES, Network.new_unordered_duplicating())
    assert (deliver, deliver, deliver) in ud_lossy
    assert (deliver, deliver, drop) in ud_lossy
    assert (deliver, drop) in ud_lossy
    assert (drop,) in ud_lossy
    assert (drop, deliver) not in ud_lossy  # drop means "never deliver again"

    # Unordered nonduplicating: exactly two copies.
    und_lossless = action_sequences(
        LossyNetwork.NO, Network.new_unordered_nonduplicating()
    )
    assert (deliver, deliver) in und_lossless
    und_lossy = action_sequences(
        LossyNetwork.YES, Network.new_unordered_nonduplicating()
    )
    assert (deliver, drop) in und_lossy
    assert (drop, drop) in und_lossy


def test_timer_semantics():
    # ref: src/actor/model.rs:1276-1330 (resets_timer and timer behavior)
    class TimerActor(Actor):
        def on_start(self, id, out):
            out.set_timer("t", model_timeout())
            return 0

        def on_timeout(self, id, state, timer, out):
            if state < 2:
                out.set_timer("t", model_timeout())
                return state + 1
            return None  # state 2: nothing — timer fires and is consumed

    checker = (
        ActorModel.new(None, None)
        .actor(TimerActor())
        .property(Expectation.ALWAYS, "any", lambda m, s: True)
        .checker()
        .spawn_bfs()
        .join()
    )
    # States: (0, timer set) -> (1, set) -> (2, set) -> (2, unset).
    assert checker.unique_state_count() == 4


def test_crash_semantics():
    # ref: src/actor/model.rs:1332-1431 — crash cancels timers, blocks delivery.
    class CrashableActor(Actor):
        def on_start(self, id, out):
            out.set_timer("tick", model_timeout())
            if id == 0:
                out.send(Id(1), "hello")
            return 0

        def on_msg(self, id, state, src, msg, out):
            return state + 1

        def on_timeout(self, id, state, timer, out):
            return None

    from stateright_tpu.actor import Crash, Timeout

    model = (
        ActorModel.new(None, None)
        .add_actors([CrashableActor(), CrashableActor()])
        .with_init_network(Network.new_unordered_nonduplicating())
        .with_max_crashes(1)
        .property(Expectation.ALWAYS, "any", lambda m, s: True)
    )
    init = model.init_states()[0]

    # Crash actions are enumerated while the budget lasts.
    actions: list = []
    model.actions(init, actions)
    assert Crash(Id(0)) in actions and Crash(Id(1)) in actions

    # Crashing cancels timers and marks the actor dead.
    crashed_state = model.next_state(init, Crash(Id(1)))
    assert crashed_state.crashed[1]
    assert crashed_state.timers_set[1] == frozenset()

    # Delivery to a crashed actor is ignored (ref: src/actor/model.rs:332-337).
    assert model.next_state(crashed_state, Deliver(Id(0), Id(1), "hello")) is None
    # The crashed actor's timers are gone, so only actor 0's timeout remains.
    actions = []
    model.actions(crashed_state, actions)
    assert Timeout(Id(1), "tick") not in actions
    assert Timeout(Id(0), "tick") in actions
    # Crash budget exhausted: no further Crash actions.
    assert not any(isinstance(a, Crash) for a in actions)

    # NOTE (reference parity): states differing only in `crashed` share a
    # fingerprint — crash states merge with no-op-timeout states during dedup,
    # exactly as in the reference whose Hash impl also excludes `crashed`
    # (ref: src/actor/model_state.rs:134-145).


def test_choose_random_creates_branches():
    # ref: src/actor.rs choose_random / on_random + SelectRandom actions.
    class RandomActor(Actor):
        def on_start(self, id, out):
            out.choose_random("coin", ["heads", "tails"])
            return "undecided"

        def on_random(self, id, state, random, out):
            return random

    recorder = StateRecorder()
    checker = (
        ActorModel.new(None, None)
        .actor(RandomActor())
        .property(Expectation.ALWAYS, "any", lambda m, s: True)
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    finals = {s.actor_states[0] for s in recorder.states}
    assert finals == {"undecided", "heads", "tails"}
    # random_choices are excluded from the fingerprint, so "undecided with
    # choices pending" and "undecided after a choice" do not double-count...
    # (both reachable states differ in actor state only).
    assert checker.unique_state_count() == 3
