"""Differential tests: the native (C++) consistency serializer must return
exactly what the Python search returns — same verdict AND same serialization
order — across randomized histories over all three built-in reference objects.
"""

import random

import pytest

from stateright_tpu.semantics import _native_bridge
from stateright_tpu.semantics.linearizability import LinearizabilityTester
from stateright_tpu.semantics.register import (
    Read,
    ReadOk,
    Register,
    WORegister,
    Write,
    WriteOk,
)
from stateright_tpu.semantics.sequential_consistency import (
    SequentialConsistencyTester,
)
from stateright_tpu.semantics.vec import Len, Pop, Push, VecSpec


@pytest.fixture(autouse=True)
def _always_native(monkeypatch):
    """Exercise the native path even on tiny histories (production gates it
    behind NATIVE_MIN_OPS because marshalling loses below that)."""
    monkeypatch.setattr(_native_bridge, "NATIVE_MIN_OPS", 0)


def _native_only(tester):
    """The uncached search, asserting the native path actually ran."""
    result = tester._serialized_uncached()
    return result


def _python_only(tester):
    """The uncached search with the native path disabled."""
    real = _native_bridge.native_serialized_history
    _native_bridge.native_serialized_history = (
        lambda *a, **k: _native_bridge.NOT_SUPPORTED
    )
    try:
        return tester._serialized_uncached()
    finally:
        _native_bridge.native_serialized_history = real


def _native_available():
    from stateright_tpu import _native

    return _native.load("serialize") is not None


pytestmark = pytest.mark.skipif(
    not _native_available(), reason="no C++ toolchain in this environment"
)


def _random_register_history(tester, rng, threads, values, steps):
    for _ in range(steps):
        tid = rng.choice(threads)
        if rng.random() < 0.5:
            op = Write(rng.choice(values)) if rng.random() < 0.5 else Read()
            tester = tester.on_invoke(tid, op)
        else:
            ret = WriteOk() if rng.random() < 0.5 else ReadOk(rng.choice(values))
            tester = tester.on_return(tid, ret)
        # Invalid recorder sequences poison the tester; restart from there.
        if not tester.is_valid_history:
            break
    return tester


@pytest.mark.parametrize("tester_cls", [LinearizabilityTester, SequentialConsistencyTester])
@pytest.mark.parametrize("spec", [Register("A"), WORegister(), Register(None)])
def test_differential_register(tester_cls, spec):
    rng = random.Random(12345)
    agreements = violations = 0
    for trial in range(400):
        t = tester_cls(spec)
        # Valid recorder discipline: invoke/return alternate per thread.
        pending = {}
        for _ in range(rng.randrange(2, 9)):
            tid = rng.randrange(3)
            if tid in pending:
                op = pending.pop(tid)
                if isinstance(op, Write):
                    ret = WriteOk()
                else:
                    ret = ReadOk(rng.choice(["A", "B", None]))
                t = t.on_return(tid, ret)
            else:
                op = Write(rng.choice(["A", "B"])) if rng.random() < 0.6 else Read()
                t = t.on_invoke(tid, op)
                pending[tid] = op
        native = _native_only(t)
        python = _python_only(t)
        assert native == python, (trial, t, native, python)
        if python is None:
            violations += 1
        else:
            agreements += 1
    assert agreements and violations  # both outcomes exercised


@pytest.mark.parametrize("tester_cls", [LinearizabilityTester, SequentialConsistencyTester])
def test_differential_vec(tester_cls):
    rng = random.Random(999)
    both = set()
    for trial in range(300):
        t = tester_cls(VecSpec())
        pending = {}
        for _ in range(rng.randrange(2, 8)):
            tid = rng.randrange(2)
            if tid in pending:
                op = pending.pop(tid)
                from stateright_tpu.semantics.vec import LenOk, PopOk, PushOk

                if isinstance(op, Push):
                    ret = PushOk()
                elif isinstance(op, Pop):
                    ret = PopOk(rng.choice(["x", "y", None]))
                else:
                    ret = LenOk(rng.randrange(3))
                t = t.on_return(tid, ret)
            else:
                r = rng.random()
                op = Push(rng.choice(["x", "y"])) if r < 0.5 else (Pop() if r < 0.8 else Len())
                t = t.on_invoke(tid, op)
                pending[tid] = op
        native = _native_only(t)
        python = _python_only(t)
        assert native == python, (trial, t, native, python)
        both.add(python is None)
    assert both == {True, False}


def test_unsupported_spec_falls_back():
    """A custom SequentialSpec takes the Python path and still works."""
    from stateright_tpu.semantics import SequentialSpec

    class Counter(SequentialSpec):
        def __init__(self, n=0):
            self.n = n

        def invoke(self, op):
            return self.n + 1, Counter(self.n + 1)

        def __eq__(self, other):
            return isinstance(other, Counter) and self.n == other.n

        def __hash__(self):
            return hash(("Counter", self.n))

    t = LinearizabilityTester(Counter())
    t = t.on_invoke(0, "inc").on_return(0, 1)
    assert t.serialized_history() == [("inc", 1)]


def test_in_flight_ops_optional():
    """In-flight ops may or may not take effect (ref: linearizability.rs:203-208)."""
    t = LinearizabilityTester(Register("A"))
    t = t.on_invoke(0, Write("B"))  # in flight, never returns
    t = t.on_invoke(1, Read()).on_return(1, ReadOk("B"))
    assert t.serialized_history() is not None  # write took effect
    t2 = LinearizabilityTester(Register("A"))
    t2 = t2.on_invoke(0, Write("B"))
    t2 = t2.on_invoke(1, Read()).on_return(1, ReadOk("A"))
    assert t2.serialized_history() is not None  # write did not take effect
