"""Stable-encoding and fingerprint tests (ref contract: src/lib.rs:340-387 —
fingerprints must be stable across runs/threads; unordered collections must
hash independently of iteration order, ref: src/util.rs)."""

import subprocess
import sys

from stateright_tpu import fingerprint, stable_encode


def test_fingerprint_nonzero_and_deterministic():
    fp = fingerprint((0, 0))
    assert fp != 0
    assert fp == fingerprint((0, 0))
    assert fingerprint((0, 1)) != fp


def test_set_encoding_is_order_independent():
    # Build sets with different insertion orders.
    s1 = set()
    for x in [3, 1, 2, 9, 7]:
        s1.add(x)
    s2 = set()
    for x in [7, 9, 2, 1, 3]:
        s2.add(x)
    assert stable_encode(s1) == stable_encode(s2)
    assert fingerprint(frozenset([1, 2])) == fingerprint(frozenset([2, 1]))


def test_dict_encoding_is_order_independent():
    d1 = {"a": 1, "b": 2}
    d2 = {"b": 2, "a": 1}
    assert stable_encode(d1) == stable_encode(d2)


def test_distinct_types_encode_distinctly():
    assert stable_encode(1) != stable_encode("1")
    assert stable_encode(True) != stable_encode(1)
    assert stable_encode(None) != stable_encode(0)


def test_nested_structures():
    v1 = (1, frozenset([(2, 3), (4, 5)]), {"k": [1, 2]})
    v2 = (1, frozenset([(4, 5), (2, 3)]), {"k": [1, 2]})
    assert fingerprint(v1) == fingerprint(v2)


def test_stable_across_processes():
    # The reason Python's hash() can't be used: PYTHONHASHSEED. Our fingerprint
    # must agree between separate interpreter processes.
    code = (
        "from stateright_tpu import fingerprint;"
        "print(fingerprint(('x', frozenset([1, 2, 3]), 42)))"
    )
    outs = {
        subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            check=True,
            cwd="/root/repo",
        ).stdout.strip()
        for _ in range(2)
    }
    assert len(outs) == 1
    assert int(outs.pop()) == fingerprint(("x", frozenset([1, 2, 3]), 42))
